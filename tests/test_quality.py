"""The quality observatory, fast tier: the QualityTracker sink (rollup /
digest / metric families / disable gate), agreement scoring, the
QualityDriftDetector fire-once contract, the AnomalyMonitor quality feed,
offline summarize_quality (incl. the pre-quality-log null contract), the
golden-set CanaryProber against a fake transport (scoring, EWMA, collapse
incident path, prune + gauge purge), the telemetry balancer's canary
down-weighting, registry canary hygiene, and the router's fleet quality
rollup. No model, no device, no sockets."""

import json
import random
import threading

import pytest

from edgemesh.fleet import CanaryProber, FleetRouter, ReplicaRegistry
from edgemesh.fleet.balancer import TelemetryBalancer
from edgemesh.fleet.canary import FALLBACK_GOLDEN, load_golden_set
from edgemesh.fleet.transport import TransportError
from edgemesh.obs import Registry
from edgemesh.obs.anomaly import AnomalyMonitor, QualityDriftDetector
from edgemesh.obs.quality import (
    CANARY_RECORD_EVENT,
    QualityTracker,
    pairwise_agreement,
    summarize_quality,
    token_f1,
)
from edgemesh.utils.tracing import JsonlLogger


def _q(conf, conf_min=None, ent=None, tokens=8):
    return {"confidence_mean": conf,
            "confidence_min": conf if conf_min is None else conf_min,
            "entropy_mean": ent, "tokens": tokens}


# ---------------------------------------------------------------------------
# QualityTracker: the engine-side sink
# ---------------------------------------------------------------------------


def test_tracker_rollup_and_digest_empty_until_first_signal():
    t = QualityTracker(registry=Registry(), enabled=True)
    assert t.rollup() == {}
    assert t.digest_quality() is None
    # Malformed / absent quality blocks are no-ops, not crashes.
    t.on_retire(None)
    t.on_retire({"confidence_mean": "nan?"})
    t.on_retire({"confidence_mean": float("nan")})
    assert t.rollup() == {}


def test_tracker_rollup_digest_and_metric_families():
    reg = Registry()
    t = QualityTracker(registry=reg, engine="continuous",
                       low_confidence=0.2, enabled=True)
    t.on_retire(_q(0.9, conf_min=0.5, ent=1.0), tenant="alice")
    t.on_retire(_q(0.1, conf_min=0.05, ent=6.0), tenant="bob")
    roll = t.rollup()
    assert roll["engine"] == "continuous"
    assert roll["requests"] == 2
    assert roll["low_confidence_requests"] == 1
    # EWMA seeded by first sample: 0.2*0.1 + 0.8*0.9 = 0.74.
    assert roll["confidence_ewma"] == pytest.approx(0.74)
    assert roll["confidence_min_seen"] == 0.05
    assert roll["entropy_ewma"] == pytest.approx(0.2 * 6.0 + 0.8 * 1.0)
    assert set(roll["tenants"]) == {"alice", "bob"}
    assert roll["tenants"]["bob"]["low"] == 1
    dig = t.digest_quality()
    assert dig == {"requests": 2,
                   "confidence_ewma": roll["confidence_ewma"],
                   "entropy_ewma": roll["entropy_ewma"],
                   "low_fraction": 0.5}
    # Metric families follow the EM111/EM112 naming + bounded labels.
    summ = reg.summary(prefix="edgemesh_quality_")
    assert summ['edgemesh_quality_confidence{engine="continuous"}']["count"] == 2
    assert summ['edgemesh_quality_entropy{engine="continuous"}']["count"] == 2
    assert summ['edgemesh_quality_requests_total{engine="continuous",band="ok"}'] == 1
    assert summ['edgemesh_quality_requests_total{engine="continuous",band="low"}'] == 1
    assert summ['edgemesh_quality_tenant_confidence{engine="continuous",tenant="alice"}'] == pytest.approx(0.9)


def test_tracker_disabled_is_a_no_op():
    reg = Registry()
    t = QualityTracker(registry=reg, enabled=False)
    t.on_retire(_q(0.9))
    assert t.rollup() == {}
    assert t.digest_quality() is None
    assert "edgemesh_quality_confidence" not in json.dumps(
        reg.summary(prefix="edgemesh_quality_confidence"))


def test_tracker_env_gate(monkeypatch):
    monkeypatch.setenv("EDGEMESH_QUALITY", "0")
    assert QualityTracker(registry=Registry()).enabled is False
    monkeypatch.setenv("EDGEMESH_QUALITY", "1")
    assert QualityTracker(registry=Registry()).enabled is True


def test_tracker_feeds_anomaly_monitor():
    monitor = AnomalyMonitor(registry=Registry())
    t = QualityTracker(registry=Registry(), enabled=True,
                       anomaly_source=lambda: monitor)
    # Healthy baseline, then a sustained collapse → exactly one incident.
    for _ in range(32):
        t.on_retire(_q(0.9))
    for _ in range(32):
        t.on_retire(_q(0.05), tenant="alice")
    kinds = [i["kind"] for i in monitor.incidents()]
    assert kinds == ["quality_drift"]


# ---------------------------------------------------------------------------
# Agreement scoring
# ---------------------------------------------------------------------------


def test_token_f1_and_pairwise_agreement():
    assert token_f1("the sky is blue", "the sky is blue") == 1.0
    assert token_f1("alpha beta", "gamma delta") == 0.0
    assert token_f1("", "") == 1.0  # unanimous silence, not breakage
    assert 0.0 < token_f1("the sky is blue", "the sky is blue today") < 1.0
    assert pairwise_agreement([]) is None
    assert pairwise_agreement(["solo"]) is None
    assert pairwise_agreement(["same text", "same text"]) == 1.0
    # Non-strings coerce to "" rather than raising.
    assert pairwise_agreement(["words here", None]) == 0.0


# ---------------------------------------------------------------------------
# QualityDriftDetector: fire-once per healthy→degraded transition
# ---------------------------------------------------------------------------


def test_drift_detector_fires_once_and_rearms_on_recovery():
    det = QualityDriftDetector(window=8, min_count=4, drop_factor=0.6,
                               half_life_s=3600.0, min_weight=4.0)
    # Healthy traffic builds the baseline without firing.
    assert not any(det.observe(0.9) for _ in range(16))
    # Collapse: exactly one fire across the whole degraded stretch.
    fired = [det.observe(0.05) for _ in range(16)]
    assert sum(fired) == 1
    # The first few degraded samples can't fire (window still healthy).
    assert fired.index(True) >= 1
    # Recovery re-arms, a second collapse fires exactly once again.
    assert not any(det.observe(0.9) for _ in range(16))
    assert sum(det.observe(0.05) for _ in range(16)) == 1


def test_drift_detector_degraded_samples_never_feed_baseline():
    det = QualityDriftDetector(window=8, min_count=4, drop_factor=0.6,
                               half_life_s=3600.0, min_weight=4.0)
    for _ in range(16):
        det.observe(0.9)
    before = det.baseline.quantile(0.5)
    for _ in range(64):
        det.observe(0.05)
    # A long degradation must not decay "healthy" toward the garbage.
    assert det.baseline.quantile(0.5) == pytest.approx(before, rel=0.05)


def test_monitor_on_quality_counts_and_dumps():
    reg = Registry()
    monitor = AnomalyMonitor(registry=reg, quality_drift=QualityDriftDetector(
        window=4, min_count=2, drop_factor=0.6,
        half_life_s=3600.0, min_weight=2.0))
    assert monitor.on_quality(None) is False
    for _ in range(8):
        monitor.on_quality(0.9)
    fired = [monitor.on_quality(0.05, detail={"engine": "continuous"})
             for _ in range(8)]
    assert sum(fired) == 1
    summ = reg.summary(prefix="edgemesh_anomaly_triggers_total")
    assert summ['edgemesh_anomaly_triggers_total{kind="quality_drift"}'] == 1
    inc = monitor.incidents()[-1]
    assert inc["kind"] == "quality_drift"
    assert inc["detail"] == {"engine": "continuous"}


# ---------------------------------------------------------------------------
# Offline: summarize_quality
# ---------------------------------------------------------------------------


def test_summarize_quality_none_on_pre_quality_logs():
    # A pre-quality span log (no quality keys anywhere) is an answer, not
    # an error: None, and the CLI prints null with rc 0 (test_obs.py pins
    # the record-schema side of this contract).
    records = [
        {"event": "request_spans", "rid": "r1", "spans": []},
        {"event": "router_spans", "spans": [{"name": "route"}]},
        "not a dict",
        {"event": "flight_dump", "kind": "slo_burst", "replica": "rep-0"},
    ]
    assert summarize_quality(records) is None
    assert summarize_quality([]) is None


def test_summarize_quality_full_views():
    records = [
        # Engine records with quality blocks (unknown future key ignored).
        {"event": "request_spans", "engine": "continuous", "tenant": "alice",
         "quality": {"confidence_mean": 0.9, "entropy_mean": 1.0,
                     "some_future_key": object}},
        {"event": "request_spans", "engine": "continuous",
         "quality": {"confidence_mean": 0.7}},
        # A flight dump header stamps the replica for following records.
        {"event": "flight_dump", "replica": "rep-1", "kind": "quality_drift",
         "incident_id": "inc-1", "trigger_ts": 5.0, "source": "rep-1"},
        {"event": "request_spans", "engine": "continuous",
         "quality": {"confidence_mean": 0.1}},
        # Router incident record + canary probe records.
        {"event": "incident", "kind": "quality_drift", "id": "inc-2",
         "ts": 6.0, "source": "rep-2"},
        {"event": CANARY_RECORD_EVENT, "replica": "rep-1", "pool": "qa",
         "score": 0.95},
        {"event": CANARY_RECORD_EVENT, "replica": "rep-1", "pool": "qa",
         "score": 0.15},
        # Ensemble span attrs carry agreement.
        {"event": "router_spans",
         "spans": [{"name": "ensemble", "agreement": 0.8}]},
    ]
    summ = summarize_quality(records)
    assert summ["quality_records"] == 6
    eng = summ["confidence"]["engines"]["continuous"]
    assert eng["n"] == 3 and eng["min"] == 0.1
    assert summ["confidence"]["tenants"]["alice"]["n"] == 1
    # The replica stamp only covers records after the dump header.
    assert summ["confidence"]["replicas"] == {
        "rep-1": {"n": 1, "mean": 0.1, "min": 0.1, "p50": 0.1, "p95": 0.1}}
    assert summ["agreement"]["n"] == 1
    canary = summ["canary"]["rep-1"]
    assert canary["probes"] == 2
    assert canary["score_min"] == 0.15 and canary["score_last"] == 0.15
    assert canary["pool"] == "qa"
    assert [d["incident_id"] for d in summ["drift_incidents"]] == [
        "inc-1", "inc-2"]
    assert summ["degraded_replicas"] == ["rep-1", "rep-2"]


# ---------------------------------------------------------------------------
# CanaryProber vs a fake transport
# ---------------------------------------------------------------------------


class FakeTransport:
    """Substring-routed fake (same shape as test_ensemble_fleet's)."""

    def __init__(self):
        self.calls = []
        self._routes = []

    def on(self, substr, handler):
        self._routes.append((substr, handler))
        return self

    def post_json(self, url, payload, timeout_s, headers=None):
        self.calls.append((url, payload))
        for substr, handler in self._routes:
            if substr in url:
                return handler(url, payload, headers or {})
        return 200, {"answer": "ok"}

    def get_json(self, url, timeout_s, headers=None):
        return 200, {}


GOLDEN = [{"question": "q1?", "reference": "alpha beta gamma"},
          {"question": "q2?", "reference": "delta epsilon"}]


def _echo_references(refs=None):
    table = {g["question"]: (refs or {}).get(g["question"], g["reference"])
             for g in GOLDEN}
    return lambda u, p, h: (200, {"answer": table[p["question"]]})


def _canary_reg():
    reg = ReplicaRegistry()
    reg.register("good", "http://good", model={"pool": "qa", "role": "qa"})
    reg.register("bad", "http://bad", model={"pool": "qa", "role": "qa"})
    return reg


def test_canary_scores_publish_three_ways(tmp_path):
    reg = _canary_reg()
    ft = FakeTransport()
    ft.on("good/generate", _echo_references())
    ft.on("bad/generate", lambda u, p, h: (200, {"answer": "zzz qqq"}))
    obs = Registry()
    log_path = tmp_path / "canary.jsonl"
    prober = CanaryProber(reg, transport=ft, golden=GOLDEN,
                          obs_registry=obs,
                          trace_log=JsonlLogger(log_path))
    results = prober.probe_once()
    # Perfect reproduction scores 1.0; garbage scores 0.0.
    assert results["good"]["score"] == 1.0
    assert results["bad"]["score"] == 0.0
    assert results["good"]["set_size"] == 2
    # 1) the registry (→ /fleetz, balancer), with freshness stamp.
    rep = reg.get("good")
    assert rep.canary["score"] == 1.0 and rep.canary_age_s() is not None
    assert reg.get("good").to_dict()["canary"]["probes"] == 1
    # 2) the per-replica gauge.
    summ = obs.summary(prefix="edgemesh_fleet_canary_score")
    assert summ['edgemesh_fleet_canary_score{replica="good"}'] == 1.0
    assert summ['edgemesh_fleet_canary_score{replica="bad"}'] == 0.0
    # 3) the span-log canary records.
    recs = JsonlLogger(log_path).read()
    assert {r["replica"] for r in recs} == {"good", "bad"}
    assert all(r["event"] == CANARY_RECORD_EVENT for r in recs)
    assert all(r["pool"] == "qa" for r in recs)


def test_canary_collapse_fires_once_and_rearms():
    reg = _canary_reg()
    ft = FakeTransport()
    ft.on("good/generate", _echo_references())
    bad_answers = {"answer": "zzz"}
    ft.on("bad/generate", lambda u, p, h: (200, dict(bad_answers)))
    fired = []
    prober = CanaryProber(reg, transport=ft, golden=GOLDEN,
                          obs_registry=Registry(), min_probes=2,
                          collapse_below=0.2,
                          on_collapse=lambda rid, inc: fired.append((rid, inc)))
    prober.probe_once()
    assert fired == []  # min_probes not reached yet
    prober.probe_once()
    prober.probe_once()
    # One collapse, for the degraded replica only, with a minted id.
    assert len(fired) == 1
    rid, incident = fired[0]
    assert rid == "bad"
    assert incident["kind"] == "quality_drift"
    assert incident["id"].startswith("inc-")
    # The degraded replica got a direct POST /incident (the router's
    # broadcast would exclude it as the source).
    inc_posts = [(u, p) for u, p in ft.calls if u.endswith("/incident")]
    assert len(inc_posts) == 1
    assert "bad" in inc_posts[0][0]
    assert inc_posts[0][1]["id"] == incident["id"]
    assert reg.get("bad").canary["collapsed"] is True
    # Recovery (rolled-back checkpoint) re-arms; next collapse fires again.
    bad_answers["answer"] = GOLDEN[0]["reference"]

    def recovered(u, p, h):
        return 200, {"answer": {g["question"]: g["reference"]
                                for g in GOLDEN}[p["question"]]}

    ft._routes = [(s, recovered if s == "bad/generate" else h)
                  for s, h in ft._routes]
    for _ in range(6):
        prober.probe_once()
    assert reg.get("bad").canary["collapsed"] is False
    ft._routes = [(s, (lambda u, p, h: (200, {"answer": "zzz"}))
                   if s == "bad/generate" else h) for s, h in ft._routes]
    for _ in range(8):
        prober.probe_once()
    assert len(fired) == 2 and fired[1][0] == "bad"


def test_canary_unreachable_round_keeps_previous_score():
    reg = _canary_reg()
    ft = FakeTransport()
    ft.on("good/generate", _echo_references())
    ft.on("bad/generate", _echo_references())
    prober = CanaryProber(reg, transport=ft, golden=GOLDEN,
                          obs_registry=Registry())
    prober.probe_once()
    assert reg.get("bad").canary["score"] == 1.0

    def down(u, p, h):
        raise TransportError("connection refused")

    ft._routes = [(s, down if s == "bad/generate" else h)
                  for s, h in ft._routes]
    results = prober.probe_once()
    # No quality evidence either way: the EWMA (and the balancer's view)
    # stays — liveness is the health prober's job, not the canary's.
    assert "bad" not in results
    assert reg.get("bad").canary["score"] == 1.0
    assert reg.get("bad").canary["probes"] == 1


def test_canary_prune_purges_state_and_gauge():
    reg = _canary_reg()
    ft = FakeTransport()
    ft.on("good/generate", _echo_references())
    ft.on("bad/generate", _echo_references())
    obs = Registry()
    prober = CanaryProber(reg, transport=ft, golden=GOLDEN, obs_registry=obs)
    prober.probe_once()
    assert 'edgemesh_fleet_canary_score{replica="bad"}' in obs.summary(
        prefix="edgemesh_fleet_canary_score")
    reg.deregister("bad")
    prober.probe_once()
    summ = obs.summary(prefix="edgemesh_fleet_canary_score")
    # Gauge child and prober state die with the replica (PR 14 leak class).
    assert 'edgemesh_fleet_canary_score{replica="bad"}' not in summ
    assert 'edgemesh_fleet_canary_score{replica="good"}' in summ
    assert "bad" not in prober._state


def test_golden_set_loader(tmp_path):
    path = tmp_path / "golden.jsonl"
    path.write_text(
        '# pinned from build 42\n'
        '{"question": "q1?", "reference": "a"}\n'
        '\n'
        '{"prompt": "q2?", "answer": "b"}\n')
    items = load_golden_set(str(path))
    assert items == [{"question": "q1?", "reference": "a"},
                     {"question": "q2?", "reference": "b"}]
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"question": "q?"}\n')
    with pytest.raises(ValueError, match="reference"):
        load_golden_set(str(bad))
    empty = tmp_path / "empty.jsonl"
    empty.write_text("# nothing\n")
    with pytest.raises(ValueError, match="empty"):
        load_golden_set(str(empty))
    assert all({"question", "reference"} <= set(g) for g in FALLBACK_GOLDEN)


# ---------------------------------------------------------------------------
# TelemetryBalancer: canary down-weighting
# ---------------------------------------------------------------------------


def _rep_with_canary(reg, rid, score, age_s, now):
    reg.register(rid, f"http://{rid}")
    reg.update_canary(rid, None if score is None else {"score": score})
    rep = reg.get(rid)
    if rep.canary_ts is not None:
        rep.canary_ts = now - age_s  # age the result under the fake clock
    return rep


def test_balancer_quality_penalty_matrix():
    now = 1000.0
    bal = TelemetryBalancer(quality_penalty_s=2.0, canary_floor=0.3,
                            canary_stale_after_s=120.0, now=lambda: now)
    reg = ReplicaRegistry()
    fresh_low = _rep_with_canary(reg, "low", 0.0, 0.0, now)
    half_aged = _rep_with_canary(reg, "aged", 0.0, 60.0, now)
    stale_low = _rep_with_canary(reg, "stale", 0.0, 300.0, now)
    healthy = _rep_with_canary(reg, "healthy", 0.9, 0.0, now)
    unprobed = _rep_with_canary(reg, "none", None, 0.0, now)
    # Fresh zero score: the full penalty.
    assert bal._quality_penalty(fresh_low) == pytest.approx(2.0)
    # Penalty decays linearly with canary age.
    assert bal._quality_penalty(half_aged) == pytest.approx(1.0)
    # Stale and missing results cost exactly 0 — scoring unchanged.
    assert bal._quality_penalty(stale_low) == 0.0
    assert bal._quality_penalty(unprobed) == 0.0
    # Above the floor: no penalty.
    assert bal._quality_penalty(healthy) == 0.0
    # The penalty rides _cost even with no load digest at all, so a
    # degraded replica loses picks to an equally-idle healthy one.
    assert bal._cost(fresh_low) > bal._cost(healthy)
    malformed = _rep_with_canary(reg, "weird", None, 0.0, now)
    reg.update_canary("weird", {"score": "NaN-ish"})
    assert bal._quality_penalty(reg.get("weird")) == 0.0


def test_balancer_prefers_healthy_over_degraded_under_equal_load():
    now = 1000.0
    bal = TelemetryBalancer(now=lambda: now)
    reg = ReplicaRegistry()
    degraded = _rep_with_canary(reg, "degraded", 0.05, 1.0, now)
    healthy = _rep_with_canary(reg, "healthy", 1.0, 1.0, now)
    picks = {rid: 0 for rid in ("degraded", "healthy")}
    for _ in range(20):
        picks[bal.pick([degraded, healthy]).rid] += 1
    assert picks["healthy"] == 20


# ---------------------------------------------------------------------------
# Registry canary hygiene
# ---------------------------------------------------------------------------


def test_registry_canary_hygiene():
    reg = ReplicaRegistry()
    reg.register("r1", "http://r1")
    reg.update_canary("r1", {"score": 0.9, "probes": 3})
    assert reg.get("r1").to_dict()["canary"]["score"] == 0.9
    # update_canary on an unknown rid is a no-op, not a crash.
    reg.update_canary("ghost", {"score": 0.1})
    # Removal purges the canary (a removed replica's quality standing
    # must not linger in /fleetz or balancer scoring).
    reg.set_state("r1", "removed")
    assert reg.get("r1").canary is None
    assert "canary" not in reg.get("r1").to_dict()
    # Re-registration (revive) starts with no canary: the new process
    # must re-earn its quality standing from a fresh probe.
    reg.update_canary("r1", {"score": 0.9})  # linger attempt on removed
    reg.register("r1", "http://r1")
    assert reg.get("r1").canary is None
    # None clears explicitly.
    reg.update_canary("r1", {"score": 0.5})
    reg.update_canary("r1", None)
    assert reg.get("r1").canary is None


# ---------------------------------------------------------------------------
# Router: the fleet quality rollup in status() → /fleetz
# ---------------------------------------------------------------------------


def test_router_status_quality_rollup():
    reg = ReplicaRegistry()
    reg.register("r1", "http://r1")
    reg.register("r2", "http://r2")
    router = FleetRouter(reg, transport=FakeTransport(),
                         obs_registry=Registry(), rng=random.Random(0))
    assert router.status()["quality"] is None  # no signal anywhere yet
    reg.update_load("r1", {"engine": "continuous", "quality": {
        "requests": 10, "confidence_ewma": 0.91, "entropy_ewma": 1.2,
        "low_fraction": 0.0}})
    reg.update_canary("r1", {"score": 0.95, "probes": 3})
    reg.update_canary("r2", {"score": 0.1, "probes": 3, "collapsed": True})
    quality = router.status()["quality"]
    assert quality["min_canary_score"] == 0.1
    assert quality["min_canary_replica"] == "r2"
    r1 = quality["replicas"]["r1"]
    assert r1["confidence_ewma"] == 0.91
    assert r1["low_fraction"] == 0.0
    assert r1["canary"]["score"] == 0.95
    assert quality["replicas"]["r2"]["canary"]["collapsed"] is True
