"""One-command real-checkpoint rehearsal (VERDICT r4 item 8).

The reference's flagship entry point is config-in, table-out over real
pretrained checkpoints (``Code/C-DAC Server/combiner_fp.py:380-474``). This
environment has no network, so the real Phi-2/Pythia/Llama snapshots can't
exist here — but the *path* they would travel can be pinned end-to-end: this
test materializes a tiny checkpoint directory in the exact layout
``save_pretrained`` produces (config.json + model.safetensors + a working
tokenizer.json/tokenizer_config.json), then drives ``edgemesh eval`` with an
``examples/ensemble_checkpoints.yaml``-shaped config straight through
HF-config sniffing → safetensors ingest → quantization → ensemble →
report JSON + per-sample JSONL.

When you have network, the same command runs the real thing:

    python -m edgemesh.cli eval --config examples/ensemble_checkpoints.yaml

with each ``model.path`` pointing at a downloaded snapshot
(docs/QUALITY.md "Running the real-checkpoint sweep").
"""

import json

import pytest


# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _write_checkpoint(dirpath, seed=0, vocab=257):
    """A complete tiny llama snapshot: weights the way save_pretrained lays
    them out, plus a functioning byte-level BPE tokenizer built offline."""
    from tokenizers import Tokenizer
    from tokenizers.decoders import ByteLevel as ByteLevelDecoder
    from tokenizers.models import BPE
    from tokenizers.pre_tokenizers import ByteLevel
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5,
        tie_word_embeddings=False, eos_token_id=vocab - 1,
    )
    torch.manual_seed(seed)
    LlamaForCausalLM(hf_cfg).eval().save_pretrained(dirpath)

    alphabet = sorted(ByteLevel.alphabet())  # 256 byte-level symbols
    vocab_map = {tok: i for i, tok in enumerate(alphabet)}
    vocab_map["<|endoftext|>"] = len(vocab_map)
    assert len(vocab_map) == vocab
    tok = Tokenizer(BPE(vocab=vocab_map, merges=[]))
    tok.pre_tokenizer = ByteLevel(add_prefix_space=False, use_regex=True)
    tok.decoder = ByteLevelDecoder()
    tok.save(str(dirpath / "tokenizer.json"))
    (dirpath / "tokenizer_config.json").write_text(json.dumps({
        "tokenizer_class": "PreTrainedTokenizerFast",
        "eos_token": "<|endoftext|>",
        "model_max_length": 128,
    }))
    return dirpath


def test_checkpoint_dir_to_ensemble_report(tmp_path, capsys):
    """Real-layout checkpoint dir → `edgemesh eval` → report, one command:
    two checkpoint-backed agents (one int8-quantized at ingest — the
    reference's quantized combo row), family auto-sniffed from config.json,
    HF tokenizer loaded from the snapshot, per-sample JSONL written."""
    from edgemesh.cli import main

    ck_a = _write_checkpoint(tmp_path / "model_a", seed=0)
    ck_b = _write_checkpoint(tmp_path / "model_b", seed=1)

    cfg_yaml = tmp_path / "ensemble.yaml"
    cfg_yaml.write_text(f"""
agents:
  - role: qa
    model:
      path: {ck_a}
      family: auto
      precision: int8
      max_seq_len: 128
    sampling: {{max_new_tokens: 6, do_sample: false, repetition_penalty: 1.0}}
  - role: qa
    model:
      path: {ck_b}
      family: auto
      precision: fp32
      max_seq_len: 128
    sampling: {{max_new_tokens: 6, do_sample: false, repetition_penalty: 1.0}}
eval:
  num_samples: 3
""")
    out_jsonl = tmp_path / "results.jsonl"
    rc = main([
        "eval", "--config", str(cfg_yaml),
        "--eval.output_jsonl", str(out_jsonl),
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["num_samples"] == 3
    for key in ("rouge1", "avg_rouge", "bleu", "confidence", "tps"):
        assert key in report, key
    rows = [json.loads(line) for line in open(out_jsonl)]
    assert len(rows) == 3
    assert all(isinstance(r["answer"], str) for r in rows)


def test_checkpoint_tokenizer_round_trips(tmp_path):
    """The offline-built tokenizer is a real HF fast tokenizer: encode and
    decode round-trip through the snapshot directory alone (the property
    serving/eval rely on for any downloaded checkpoint)."""
    from edgemesh.models.tokenizer import load_tokenizer

    ck = _write_checkpoint(tmp_path / "model", seed=0)
    tok = load_tokenizer(ck)
    ids = tok.encode("where is the eiffel tower?")
    assert ids and all(0 <= i < 257 for i in ids)
    assert tok.decode(ids) == "where is the eiffel tower?"
    assert tok.eos_id == 256
